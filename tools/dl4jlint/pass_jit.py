"""JIT1xx — jit-purity / host-sync pass.

A stray ``float(x)`` / ``.item()`` / ``print(x)`` inside a jitted
program either fails at trace time or — worse, under ``jnp`` arrays
outside jit — silently synchronizes the host with the device, the exact
framework-level overhead class PAPERS.md 2001.04206 measures dominating
Java DL frameworks.  Python-level RNG inside a traced function is a
different bug with the same shape: it bakes ONE sample into the
compiled program, so every step reuses it.

The pass finds *traced* functions three ways (the idioms this repo
actually uses, see parallel/ and nn/):

1. decorated with ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``
   / ``@jax.pmap`` / ``@functools.partial(jax.pmap, ...)``;
2. passed by name to ``jax.jit(f)`` / ``jit(f)`` / ``pmap(f)`` /
   ``lax.scan(f, ...)`` / ``jax.lax.scan`` / ``lax.associative_scan``
   anywhere in the same file (lambdas passed inline count too);
3. defined inside a ``_make_*`` factory and returned — the
   ``self._step = jax.jit(self._make_train_step())`` idiom, where the
   inner def IS the jitted body.

Inside a traced function (and its nested defs/lambdas) it flags:

- JIT101  ``float``/``int``/``bool`` on a non-static value
- JIT102  ``.item()`` / ``.tolist()``
- JIT103  ``np.asarray`` / ``np.array`` on a traced value
- JIT104  ``print``
- JIT105  ``time.*`` reads (wall-clock inside a program is a constant)
- JIT106  Python / numpy RNG (``random.*``, ``np.random.*``)

Static escapes: arguments mentioning ``.shape`` / ``.ndim`` / ``.size``
/ ``.dtype`` / ``len(...)`` are trace-time Python values, not traced
arrays — ``int(x.shape[0])`` is fine and not flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Set

from .engine import FileContext, Finding, LintPass

_JIT_NAMES = {"jit", "pmap", "vmap_jit"}
_SCAN_NAMES = {"scan", "associative_scan"}
_CAST_NAMES = {"float", "int", "bool", "complex"}
_ITEM_ATTRS = {"item", "tolist"}
_NP_MODULES = {"np", "numpy", "onp"}
_STATIC_ATTRS = {"shape", "ndim", "size", "dtype", "aval", "sharding"}


# the jitted-step attributes the repo actually binds (exact names plus
# the `_jit_*` cache family) — a loose `_step`/`_jit` prefix would
# false-positive on helpers like `_step_count` or `_jitter`
_STEP_ATTRS = {"_step", "_step_fn", "_chunk_step", "_decode_step"}


def _is_step_attr(attr: str) -> bool:
    return attr in _STEP_ATTRS or attr.startswith("_jit_")


def _is_jit_ref(node: ast.AST) -> bool:
    """`jit` / `jax.jit` / `jax.pmap` as an expression."""
    if isinstance(node, ast.Name):
        return node.id in _JIT_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _JIT_NAMES
    return False


def _is_scan_ref(node: ast.AST) -> bool:
    """`lax.scan` / `jax.lax.scan` / `lax.associative_scan`."""
    return isinstance(node, ast.Attribute) and node.attr in _SCAN_NAMES


def _is_partial_ref(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return node.id == "partial"
    return isinstance(node, ast.Attribute) and node.attr == "partial"


def _decorator_is_jit(dec: ast.AST) -> bool:
    if _is_jit_ref(dec):
        return True
    if isinstance(dec, ast.Call):
        if _is_jit_ref(dec.func):                # @jax.jit(static_...)
            return True
        if _is_partial_ref(dec.func):            # @partial(jax.jit, ...)
            return any(_is_jit_ref(a) for a in dec.args)
    return False


def _mentions_static(node: ast.AST) -> bool:
    """True when the expression reads trace-time-static metadata
    (`x.shape`, `len(x)`, ...) — casting THAT to int is pure."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
            return True
        if (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id == "len"):
            return True
    return False


def _collect_traced(tree: ast.AST) -> List[ast.AST]:
    """Every FunctionDef / Lambda node in the file that is traced."""
    traced: List[ast.AST] = []
    jitted_names: Set[str] = set()

    for node in ast.walk(tree):
        # idiom 1: decorators
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_decorator_is_jit(d) for d in node.decorator_list):
                traced.append(node)
        # idiom 2: f passed to jit/pmap/scan
        if isinstance(node, ast.Call) and (
                _is_jit_ref(node.func) or _is_scan_ref(node.func)):
            cands = list(node.args[:1])
            for kw in node.keywords:
                if kw.arg in ("fun", "f", "body_fun"):
                    cands.append(kw.value)
            for cand in cands:
                if isinstance(cand, ast.Lambda):
                    traced.append(cand)
                elif isinstance(cand, ast.Name):
                    jitted_names.add(cand.id)
        # idiom 3: inner def returned from a _make_* factory
        if (isinstance(node, ast.FunctionDef)
                and node.name.startswith(("_make_", "make_"))):
            returned = {
                r.value.id for r in ast.walk(node)
                if isinstance(r, ast.Return)
                and isinstance(r.value, ast.Name)}
            for sub in ast.walk(node):
                if (isinstance(sub, ast.FunctionDef)
                        and sub.name in returned):
                    traced.append(sub)

    if jitted_names:
        for node in ast.walk(tree):
            if (isinstance(node, ast.FunctionDef)
                    and node.name in jitted_names):
                traced.append(node)
    # dedupe while keeping order
    seen: Set[int] = set()
    out = []
    for n in traced:
        if id(n) not in seen:
            seen.add(id(n))
            out.append(n)
    return out


class JitPurityPass(LintPass):
    name = "jit"
    description = ("flag host syncs, I/O and Python RNG inside traced "
                   "(jit/pmap/scan) functions")
    codes = {
        "JIT101": "float/int/bool cast of a traced value",
        "JIT102": ".item()/.tolist() host sync",
        "JIT103": "np.asarray/np.array on a traced value",
        "JIT104": "print inside a traced function",
        "JIT105": "wall-clock read inside a traced function",
        "JIT106": "Python-level RNG inside a traced function",
        "JIT107": "unconditional host sync of a jitted step's result",
    }

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        yield from self._sync_on_step_path(ctx)
        traced = _collect_traced(ctx.tree)
        seen = set()        # a def nested in a traced def is walked by
        for fn in traced:   # both — report each site exactly once
            name = getattr(fn, "name", "<lambda>")
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            for stmt in body:
                for f in self._scan(ctx, name, stmt):
                    site = (f.line, f.col, f.code)
                    if site not in seen:
                        seen.add(site)
                        yield f

    def _scan(self, ctx: FileContext, scope: str,
              node: ast.AST) -> Iterator[Finding]:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            # JIT101: float(x) on a traced (non-constant, non-static) arg
            if (isinstance(f, ast.Name) and f.id in _CAST_NAMES
                    and sub.args
                    and not isinstance(sub.args[0], ast.Constant)
                    and not _mentions_static(sub.args[0])):
                yield self._f(ctx, sub, "JIT101", scope, f.id,
                              f"`{f.id}(...)` forces a host sync on a "
                              f"traced value (trace-time error under "
                              f"jit, silent device round-trip outside)")
            # JIT102: .item() / .tolist()
            elif isinstance(f, ast.Attribute) and f.attr in _ITEM_ATTRS:
                yield self._f(ctx, sub, "JIT102", scope, f.attr,
                              f"`.{f.attr}()` is a host sync — keep "
                              f"the value on device or move it outside "
                              f"the jitted program")
            # JIT103: np.asarray / np.array
            elif (isinstance(f, ast.Attribute)
                    and f.attr in ("asarray", "array")
                    and isinstance(f.value, ast.Name)
                    and f.value.id in _NP_MODULES):
                yield self._f(ctx, sub, "JIT103", scope,
                              f"{f.value.id}.{f.attr}",
                              f"`{f.value.id}.{f.attr}` materializes a "
                              f"traced value on host — use jnp inside "
                              f"traced code")
            # JIT104: print
            elif isinstance(f, ast.Name) and f.id == "print":
                yield self._f(ctx, sub, "JIT104", scope, "print",
                              "`print` inside a traced function runs "
                              "once at trace time (use "
                              "jax.debug.print for runtime values)")
            # JIT105: time.*
            elif (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "time"):
                yield self._f(ctx, sub, "JIT105", scope,
                              f"time.{f.attr}",
                              f"`time.{f.attr}()` inside a traced "
                              f"function is baked in as a constant — "
                              f"time around the dispatch, not inside "
                              f"the program")
            # JIT106: random.* / np.random.*
            elif isinstance(f, ast.Attribute) and (
                    (isinstance(f.value, ast.Name)
                     and f.value.id == "random")
                    or (isinstance(f.value, ast.Attribute)
                        and f.value.attr == "random"
                        and isinstance(f.value.value, ast.Name)
                        and f.value.value.id in _NP_MODULES)):
                yield self._f(ctx, sub, "JIT106", scope,
                              f"random.{f.attr}",
                              "Python/numpy RNG inside a traced "
                              "function bakes ONE sample into the "
                              "compiled program — thread a "
                              "jax.random key instead")

    # ---- JIT107: sync-on-step-path ---------------------------------------

    def _sync_on_step_path(self, ctx: FileContext) -> Iterator[Finding]:
        """The driver-side cousin of JIT101: a function that unpacks the
        result of a jitted step (``..., loss = self._step(...)``) and
        then UNCONDITIONALLY casts it to a Python scalar blocks the host
        on every call — back-to-back steps can no longer pipeline on the
        device.  The blessed patterns stay quiet: a cast behind an
        ``if due:`` listener gate (conditional), and a sync *wrapper*
        like ``float(self.fit_batch_async(...))`` (inline cast of a
        call, not of an unpacked name) — the wrapper IS the sync API,
        the hot loop is the async sibling."""
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            device_names = set()
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)
                        and isinstance(node.value.func, ast.Attribute)
                        and _is_step_attr(node.value.func.attr)):
                    continue
                for tgt in node.targets:
                    elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                    device_names.update(
                        e.id for e in elts if isinstance(e, ast.Name))
            if not device_names:
                continue
            yield from self._unconditional_casts(ctx, fn, device_names)

    def _unconditional_casts(self, ctx: FileContext, fn: ast.AST,
                             device_names) -> Iterator[Finding]:
        # "conditional" means a real branch: If/IfExp arms, and a Try's
        # handlers/orelse.  A try BODY and a finally run every
        # iteration — wrapping the per-step sync in try/finally (the
        # supervisor plane's retry style) must not exempt it.
        def walk(node, under_if: bool):
            for field, value in ast.iter_fields(node):
                cond = under_if
                if (isinstance(node, (ast.If, ast.IfExp))
                        and field != "test"):
                    # the TEST of a branch runs every time — only the
                    # arms are conditional
                    cond = True
                elif (isinstance(node, ast.Try)
                        and field in ("handlers", "orelse")):
                    cond = True
                children = value if isinstance(value, list) else [value]
                for child in children:
                    if not isinstance(child, ast.AST):
                        continue
                    if (not cond
                            and isinstance(child, ast.Call)
                            and isinstance(child.func, ast.Name)
                            and child.func.id in _CAST_NAMES
                            and child.args
                            and isinstance(child.args[0], ast.Name)
                            and child.args[0].id in device_names):
                        yield self._f(
                            ctx, child, "JIT107", fn.name, child.func.id,
                            f"`{child.func.id}({child.args[0].id})` "
                            f"blocks the host on EVERY step — return "
                            f"the device array (fit_batch_async "
                            f"discipline) and sync only when a "
                            f"listener/report is due")
                    if not isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.Lambda)):
                        yield from walk(child, cond)

        yield from walk(fn, False)

    @staticmethod
    def _f(ctx: FileContext, node: ast.AST, code: str, scope: str,
           symbol: str, message: str) -> Finding:
        return Finding(path=ctx.rel, line=node.lineno,
                       col=node.col_offset, code=code, scope=scope,
                       symbol=symbol, message=message)
