"""PGD3xx — paged-pool gather pass.

ISSUE-18 replaced the decode path's full-history gather (`fk[gidx]`:
materialize every lane's ``MP*ps``-row logical history before softmax)
with a Pallas kernel that walks the block table in VMEM — the single
biggest per-token HBM saving in the serving plane.  That win is easy to
lose silently: one convenient ``take_along_axis`` or fancy-index gather
of the page pool on a decode-path function and the bandwidth tax is
back, with no test failing (the gather is numerically correct — it is
only *slow*).

This pass makes the tax visible at review time.  Inside DECODE-PATH
functions (name matching attention/decode/prefill/forward/verify/step,
in ``parallel/`` or ``serving/`` — the modules that dispatch per token)
it flags:

- PGD301  a fancy-index gather ``pool[idx]`` of a page-pool buffer
  (names like ``fk``/``fv``/``layer_k``/``cache_v``/``k_pages``…)
  where the subscript is a computed index array, i.e. an advanced-
  indexing gather rather than a slice; and
  ``jnp.take_along_axis(pool, ...)`` / ``jnp.take(pool, ...)`` on the
  same buffers.

Plain slices (``pool[0]``, ``pool[:, 3]``, ``pool[i, :need]``) are
structural access, not history gathers, and are not flagged.  The ONE
legitimate remaining gather — the parity oracle in
``generation._paged_attn`` — is frozen in the baseline; anything new
must either ride the kernel or carry a ``# noqa: PGD301 — reason``
pragma.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional

from .engine import FileContext, Finding, LintPass

# buffer names that hold (a view of) the KV page pool in this codebase
_POOL_NAME = re.compile(
    r"^(fk|fv|hk|hv|layer_k|layer_v|cache_k|cache_v|pool_k|pool_v|"
    r"k_pages|v_pages|pages_k|pages_v)\d*$")

# functions that sit on the per-token dispatch path
_DECODE_FN = re.compile(
    r"(attn|attention|decode|prefill|forward|verify|step)", re.IGNORECASE)

# only the device-dispatch homes; tools/tests/nn math are out of scope
_SCOPE_PREFIXES = ("deeplearning4j_tpu/parallel/",
                   "deeplearning4j_tpu/serving/")

_GATHER_CALLS = {"take_along_axis", "take"}


def _pool_name(node: ast.AST) -> Optional[str]:
    """The pool-ish identifier behind `node`, unwrapping the reshape /
    astype / .at chains the scatter path builds (``fk.reshape(...)`` is
    still the pool)."""
    while True:
        if isinstance(node, ast.Name):
            return node.id if _POOL_NAME.match(node.id) else None
        if isinstance(node, ast.Attribute):
            node = node.value
            continue
        if isinstance(node, ast.Call):
            node = node.func
            continue
        return None


def _is_computed_index(idx: ast.AST) -> bool:
    """True for advanced-indexing gathers: the subscript is (or
    contains) a computed index ARRAY — a bare name (``fk[gidx]``), a
    call, or arithmetic — rather than constants/slices, which address
    structure, not history."""
    if isinstance(idx, ast.Tuple):
        return any(_is_computed_index(e) for e in idx.elts)
    if isinstance(idx, (ast.Slice, ast.Constant)):
        return False
    if isinstance(idx, ast.UnaryOp) and isinstance(
            idx.operand, ast.Constant):
        return False                       # pool[-1]
    return True


class PagedGatherPass(LintPass):
    name = "pagedgather"
    description = ("flag full-history page-pool gathers on decode "
                   "paths (the HBM tax the paged kernel removed)")
    codes = {
        "PGD301": "page-pool history gather on a decode path — walk "
                  "the block table in the kernel instead",
    }

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.rel.startswith(_SCOPE_PREFIXES):
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if not _DECODE_FN.search(fn.name):
                continue
            yield from self._scan_fn(ctx, fn)

    def _scan_fn(self, ctx: FileContext, fn) -> Iterator[Finding]:
        for node in ast.walk(fn):
            if isinstance(node, ast.Subscript):
                if isinstance(node.value, ast.Attribute) and \
                        node.value.attr == "at":
                    # `pool.at[idx].set(...)` is the SCATTER — O(fed
                    # columns) traffic, the write half the kernel
                    # shares — not a history gather
                    continue
                name = _pool_name(node.value)
                if name and _is_computed_index(node.slice):
                    yield Finding(
                        path=ctx.rel, line=node.lineno,
                        col=node.col_offset, code="PGD301",
                        scope=fn.name, symbol=name,
                        message=f"fancy-index gather of page pool "
                                f"`{name}` in decode-path "
                                f"`{fn.name}` re-materializes the "
                                f"full history — use "
                                f"paged_flash_attention")
            elif isinstance(node, ast.Call):
                f = node.func
                attr = (f.attr if isinstance(f, ast.Attribute)
                        else f.id if isinstance(f, ast.Name) else None)
                if attr not in _GATHER_CALLS or not node.args:
                    continue
                name = _pool_name(node.args[0])
                if name:
                    yield Finding(
                        path=ctx.rel, line=node.lineno,
                        col=node.col_offset, code="PGD301",
                        scope=fn.name, symbol=name,
                        message=f"{attr}() gather of page pool "
                                f"`{name}` in decode-path "
                                f"`{fn.name}` — use "
                                f"paged_flash_attention")
