"""The dl4jlint engine: shared file walker, pass protocol, pragma and
baseline handling.

Design (mirrors what made lint_excepts.py cheap enough for tier-1):

- **One parse per file.**  The walker reads and ``ast.parse``s each
  ``deeplearning4j_tpu/**/*.py`` once and hands every pass the same
  `FileContext` (tree + source + line cache), so adding a pass costs one
  AST walk, not one filesystem sweep.
- **Pragmas.**  A finding whose source line carries ``# noqa: <CODE>``
  (or a bare ``# noqa``) is suppressed — unless the pass marked it
  ``respect_pragma=False`` (the serving/ strict-mode semantics from
  lint_excepts: some bug classes must not be smuggleable by comment).
- **Baseline.**  Pre-existing findings are frozen in
  ``lint_baseline.json`` keyed by ``path::code::scope::symbol`` with a
  count — deliberately NOT by line number, so unrelated edits above a
  frozen finding don't thaw it.  Any finding whose key count exceeds the
  baseline is NEW and fails.  ``--baseline-update`` rewrites the file
  sorted, so its diffs review like code.

Stays stdlib-only: the linter must run before (and without) jax.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

PACKAGE = "deeplearning4j_tpu"
BASELINE_PATH = pathlib.Path(__file__).resolve().parent / \
    "lint_baseline.json"

# `# noqa` / `# noqa: LCK101` / `# noqa: LCK101,JIT104 — reason`
_NOQA_RE = re.compile(r"#\s*noqa(?::\s*(?P<codes>[A-Z0-9, ]+))?",
                      re.IGNORECASE)


def line_has_noqa(line: str, code: str, allow_bare: bool = True) -> bool:
    """True when `line` carries a ``# noqa`` covering `code` (a bare
    ``# noqa`` covers every code; comma lists work).  The ONE pragma
    grammar, shared by the engine filter and passes that do their own
    suppression.  ``allow_bare=False`` demands the explicit code — for
    gates (BLE001) where a justification must name the bug class, so a
    bare ``# noqa`` left for some other tool cannot smuggle one."""
    m = _NOQA_RE.search(line)
    if m is None:
        return False
    codes = m.group("codes")
    if codes is None:
        return allow_bare
    wanted = {c.strip().upper() for c in codes.split(",") if c.strip()}
    return code.upper() in wanted


@dataclass(frozen=True)
class Finding:
    """One lint finding.  ``key`` is the baseline identity: file + code
    + lexical scope + the symbol the finding is about — line numbers are
    display-only so baselines survive unrelated edits."""

    path: str          # repo-relative posix path
    line: int
    col: int
    code: str          # e.g. "LCK101"
    scope: str         # "Class.method", "func", or "<module>"
    symbol: str        # the attribute / call the finding is about
    message: str
    respect_pragma: bool = True

    @property
    def key(self) -> str:
        return f"{self.path}::{self.code}::{self.scope}::{self.symbol}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.code} "
                f"[{self.scope}] {self.message}")


@dataclass
class FileContext:
    """Everything a pass needs about one file, parsed exactly once."""

    rel: str                    # repo-relative posix path
    path: pathlib.Path          # absolute
    source: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def has_pragma(self, lineno: int, code: str) -> bool:
        """True when `lineno` carries a ``# noqa`` that covers `code`
        (bare noqa covers every code)."""
        return line_has_noqa(self.line(lineno), code)


class LintPass:
    """Base class for a pass.  Subclasses set `name`, `codes` (code ->
    one-line description) and implement `run(ctx)` yielding Findings.
    The engine applies pragma suppression afterwards; passes that need
    strict (pragma-proof) semantics emit respect_pragma=False."""

    name: str = "pass"
    description: str = ""
    codes: Dict[str, str] = {}

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        raise NotImplementedError
        yield  # pragma: no cover


def default_passes() -> List[LintPass]:
    # imported lazily so `from tools.dl4jlint.engine import Finding`
    # never drags every pass (and their module-level tables) in
    from . import (pass_excepts, pass_jit, pass_locks, pass_pagedgather,
                   pass_recompile)
    return [pass_locks.LockDisciplinePass(),
            pass_jit.JitPurityPass(),
            pass_recompile.RecompileHazardPass(),
            pass_pagedgather.PagedGatherPass(),
            pass_excepts.BroadExceptPass()]


def iter_files(root: pathlib.Path) -> Iterator[pathlib.Path]:
    pkg = root / PACKAGE
    yield from sorted(pkg.rglob("*.py"))


def _make_context(root: pathlib.Path, path: pathlib.Path):
    """(FileContext, syntax_error_finding_or_None) for one file."""
    rel = path.relative_to(root).as_posix()
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        # surfaced as an un-pragma-able finding rather than a crash: a
        # file the linter cannot parse is itself a tier-1 failure
        return FileContext(rel=rel, path=path, source=source,
                           tree=ast.Module(body=[], type_ignores=[]),
                           lines=source.splitlines()), Finding(
            path=rel, line=e.lineno or 0, col=e.offset or 0,
            code="SYN001", scope="<module>", symbol="syntax",
            message=f"file does not parse: {e.msg}",
            respect_pragma=False)
    return FileContext(rel=rel, path=path, source=source, tree=tree,
                       lines=source.splitlines()), None


def run_passes(root: pathlib.Path,
               passes: Optional[Sequence[LintPass]] = None,
               select: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run `passes` (default: all four) over every package file under
    `root`.  `select` filters by pass name or code prefix (e.g.
    ``["locks"]`` or ``["LCK"]``).  Returns pragma-filtered findings
    sorted by (path, line, code)."""
    passes = list(default_passes() if passes is None else passes)
    if select:
        sel = {s.strip().lower() for s in select if s.strip()}
        matched = {s for s in sel for p in passes
                   if p.name.lower() == s
                   or any(code.lower().startswith(s) for code in p.codes)}
        if sel - matched:
            # a typo'd selector must fail loudly, not green-light an
            # empty pass list forever
            raise ValueError(
                f"--select matched no pass: {sorted(sel - matched)} "
                f"(passes: {[p.name for p in passes]})")
        passes = [p for p in passes
                  if p.name.lower() in sel
                  or any(code.lower().startswith(s)
                         for code in p.codes for s in sel)]
    findings: List[Finding] = []
    for path in iter_files(root):
        ctx, syntax_error = _make_context(root, path)
        if syntax_error is not None:
            findings.append(syntax_error)
            continue
        for p in passes:
            for f in p.run(ctx):
                if f.respect_pragma and ctx.has_pragma(f.line, f.code):
                    continue
                findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.code, f.col))
    return findings


# ---- baseline ------------------------------------------------------------

def baseline_counts(findings: Iterable[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key] = counts.get(f.key, 0) + 1
    return counts


def load_baseline(path: pathlib.Path = BASELINE_PATH) -> Dict[str, int]:
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def render_baseline(findings: Iterable[Finding]) -> str:
    """Sorted, diff-friendly JSON for `--baseline-update`."""
    counts = baseline_counts(findings)
    return json.dumps(
        {"version": 1,
         "comment": "frozen pre-existing findings — run "
                    "`python -m tools.dl4jlint --baseline-update` after "
                    "reviewing; new findings must be FIXED, not frozen",
         "findings": {k: counts[k] for k in sorted(counts)}},
        indent=2, sort_keys=False) + "\n"


def new_findings(findings: Sequence[Finding],
                 baseline: Dict[str, int]) -> List[Finding]:
    """The findings NOT covered by the baseline.  For each key, the
    first `baseline[key]` occurrences (by line order) are frozen; any
    excess is new.  A baselined key that shrank is simply satisfied —
    `--baseline-update` tightens the file."""
    remaining = dict(baseline)
    out: List[Finding] = []
    for f in findings:
        left = remaining.get(f.key, 0)
        if left > 0:
            remaining[f.key] = left - 1
        else:
            out.append(f)
    return out
