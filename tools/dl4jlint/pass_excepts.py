"""BLE0xx — broad exception handlers (absorbed from tools/lint_excepts.py).

Semantics are unchanged from the original (ISSUE-1/4/8/10 history):

- **Relaxed mode** (most of the package): a bare ``except:`` /
  ``except Exception`` / ``except BaseException`` needs a
  ``# noqa: BLE001 — reason`` pragma; ALLOWLIST may grant a per-file
  ceiling (kept empty).
- **Strict mode** (``serving/``, ``obs/``, ``runtime/launcher.py``): a
  pragma alone is NOT enough — every broad handler, pragma'd or not,
  counts against an explicit per-file ceiling (the documented
  group-failure isolators and worker-survival backstops).  Excess
  handlers are BLE002 findings that no pragma can suppress.

``tools/lint_excepts.py`` is now a thin shim over this module; the
public helpers (`broad_handlers`, `main`) and tables keep their exact
historical behavior so tests/test_lint_excepts.py passes unchanged.
"""

from __future__ import annotations

import ast
import pathlib
import sys
from typing import Iterator, List, Tuple

from .engine import FileContext, Finding, LintPass, line_has_noqa

# path (relative to repo root) -> max number of un-pragma'd broad
# handlers tolerated.  Keep this EMPTY: new broad handlers should either
# be narrowed or carry a justified `noqa: BLE001` pragma.
ALLOWLIST: dict = {}

# Under serving/ the bar is higher (ISSUE-4): the request path is where
# a swallowed AttributeError becomes a silent wrong answer at scale, so
# a `noqa: BLE001` pragma alone is NOT enough — every broad handler,
# pragma'd or not, must be accounted for here with its exact ceiling.
# The documented sites are the group-failure isolators (a dispatch
# group / decode step must fail its OWN requests whatever the device
# raised) and the worker-survival backstops (the worker thread must
# outlive any group failure, or every future submit hangs on a dead
# queue).
SERVING_ALLOWLIST: dict = {
    "deeplearning4j_tpu/serving/batcher.py": 2,  # _execute bisector +
                                                 # _run survival backstop
    "deeplearning4j_tpu/serving/lm.py": 1,       # _run fail-in-flight
    "deeplearning4j_tpu/serving/fleet.py": 1,    # _FleetHandler.do_POST
                                                 # catch-all: the fleet
                                                 # front must keep
                                                 # serving (500 once,
                                                 # typed stay 4xx/503)
    "deeplearning4j_tpu/serving/procfleet.py": 1,  # supervision-loop
                                                   # survival backstop:
                                                   # a bug in one sweep
                                                   # must not end ALL
                                                   # future restarts
}
SERVING_PREFIX = "deeplearning4j_tpu/serving/"

# The process launcher gets the strict bar too (ISSUE-10): a swallowed
# exception around spawn/reap/kill is how zombies and orphaned worker
# process groups hide — no broad handlers at all, pragma'd or not.
LAUNCHER_ALLOWLIST: dict = {}
LAUNCHER_PREFIX = "deeplearning4j_tpu/runtime/launcher.py"

# The observability plane gets the same strict bar (ISSUE-8): a
# swallowed exception inside a metrics/trace hook silently blinds the
# system right when something is going wrong — no broad handlers at
# all, pragma'd or not.
OBS_ALLOWLIST: dict = {}
OBS_PREFIX = "deeplearning4j_tpu/obs/"

# The KV page-shipping wire plane (ISSUE-14) carries serving state
# BETWEEN processes: a swallowed parse/integrity error here installs
# silent garbage KV on a decode worker — no broad handlers at all,
# pragma'd or not.  Listed before the serving/ prefix so the ceiling
# stays explicitly EMPTY even if serving/ ever grows an entry for it.
TRANSFER_ALLOWLIST: dict = {}
TRANSFER_PREFIX = "deeplearning4j_tpu/serving/transfer.py"

# The overload-survival policy plane (ISSUE-15) decides WHO gets the
# KV pool under pressure: a swallowed error here silently starves or
# wrongly preempts a priority class — no broad handlers at all,
# pragma'd or not.  Same explicit-empty treatment as transfer.py.
PRESSURE_ALLOWLIST: dict = {}
PRESSURE_PREFIX = "deeplearning4j_tpu/serving/pressure.py"

# The tenancy policy plane (ISSUE-16) decides WHOSE request is
# admitted, throttled, or sacrificed: a swallowed error here silently
# over-bills or starves a tenant — no broad handlers at all, pragma'd
# or not.  Same explicit-empty treatment as pressure.py.
TENANCY_ALLOWLIST: dict = {}
TENANCY_PREFIX = "deeplearning4j_tpu/serving/tenancy.py"

# The tiered KV state hierarchy (ISSUE-19) persists session KV across
# processes: a swallowed integrity/manifest error here resumes silent
# garbage KV hours later — durability failures must stay OSError-narrow
# and surface as the typed SwapEvictedError/PageShipError ladder.  No
# broad handlers at all, pragma'd or not.
HIBERNATE_ALLOWLIST: dict = {}
HIBERNATE_PREFIX = "deeplearning4j_tpu/serving/hibernate.py"

# prefix -> (allowlist, label) for the strict-mode passes (first match
# wins, so file-level prefixes go before their parent directory)
STRICT_PREFIXES = (
    (TRANSFER_PREFIX, TRANSFER_ALLOWLIST, "TRANSFER_ALLOWLIST"),
    (PRESSURE_PREFIX, PRESSURE_ALLOWLIST, "PRESSURE_ALLOWLIST"),
    (TENANCY_PREFIX, TENANCY_ALLOWLIST, "TENANCY_ALLOWLIST"),
    (HIBERNATE_PREFIX, HIBERNATE_ALLOWLIST, "HIBERNATE_ALLOWLIST"),
    (SERVING_PREFIX, SERVING_ALLOWLIST, "SERVING_ALLOWLIST"),
    (OBS_PREFIX, OBS_ALLOWLIST, "OBS_ALLOWLIST"),
    (LAUNCHER_PREFIX, LAUNCHER_ALLOWLIST, "LAUNCHER_ALLOWLIST"),
)

PACKAGE = "deeplearning4j_tpu"
PRAGMA = "noqa: BLE001"


def _is_broad(handler: ast.ExceptHandler) -> bool:
    """True for ``except:``, ``except Exception``, ``except
    BaseException``, including tuple forms that contain either."""
    t = handler.type
    if t is None:
        return True

    def broad_name(node) -> bool:
        return isinstance(node, ast.Name) and node.id in (
            "Exception", "BaseException")

    if isinstance(t, ast.Tuple):
        return any(broad_name(el) for el in t.elts)
    return broad_name(t)


def broad_handlers(path: pathlib.Path, respect_pragma: bool = True):
    """Yield (lineno, line) for each broad handler in `path`.  With
    `respect_pragma` (the default), handlers whose except line carries
    a ``noqa`` naming BLE001 (comma lists work; a bare ``# noqa`` does
    NOT count — the justification must name the bug class) are skipped;
    `respect_pragma=False` counts EVERY broad handler — the serving/
    strict mode."""
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError as e:
        yield (e.lineno or 0, f"<syntax error: {e}>")
        return
    # delegate to the ONE walk the tier-1 gate runs, so the legacy API
    # can never drift from the pass
    ctx = FileContext(rel=str(path), path=path, source=source, tree=tree,
                      lines=source.splitlines())
    yield from _handlers_in_ctx(ctx, respect_pragma)


def _handlers_in_ctx(ctx: FileContext,
                     respect_pragma: bool) -> List[Tuple[int, str]]:
    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.ExceptHandler) and _is_broad(node):
            line = ctx.line(node.lineno)
            if not respect_pragma or not line_has_noqa(
                    line, "BLE001", allow_bare=False):
                out.append((node.lineno, line.strip()))
    return out


class BroadExceptPass(LintPass):
    name = "excepts"
    description = ("fail on new broad `except Exception:` handlers; "
                   "strict (pragma-proof) ceilings under serving/obs/"
                   "launcher")
    codes = {
        "BLE001": "broad except handler without a justified pragma",
        "BLE002": "broad handler over the strict-mode allowlist ceiling",
    }

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        strict = next(((allow, label)
                       for prefix, allow, label in STRICT_PREFIXES
                       if ctx.rel.startswith(prefix)), None)
        if strict is not None:
            # strict mode subsumes the relaxed pragma check: count
            # EVERY broad handler (pragma'd or not) against the
            # explicit allowlist ceiling — BLE002 ignores pragmas
            allow, label = strict
            every = _handlers_in_ctx(ctx, respect_pragma=False)
            ceiling = allow.get(ctx.rel, 0)
            for lineno, line in every[ceiling:]:
                yield Finding(
                    path=ctx.rel, line=lineno, col=0, code="BLE002",
                    scope="<module>", symbol="except",
                    message=(f"broad except handler exceeds the "
                             f"{label} ceiling ({ceiling}) — narrow "
                             f"it or (if it really is a group-failure "
                             f"isolator) raise the ceiling with a "
                             f"review: {line}"),
                    respect_pragma=False)
            return
        found = _handlers_in_ctx(ctx, respect_pragma=True)
        allowed = ALLOWLIST.get(ctx.rel, 0)
        for lineno, line in found[allowed:]:
            # pragma already consumed above (PRAGMA check) — emit as
            # pragma-proof so the engine does not double-filter on a
            # bare `# noqa` without the BLE001 code
            yield Finding(
                path=ctx.rel, line=lineno, col=0, code="BLE001",
                scope="<module>", symbol="except",
                message=(f"broad except handler without '{PRAGMA}' "
                         f"pragma: {line}"),
                respect_pragma=False)


def main(argv=None) -> int:
    """Historical lint_excepts CLI (exit 0 clean / 1 with one line per
    offender) — now a thin driver over `BroadExceptPass` through the
    engine, so the strict/relaxed ceiling logic exists exactly once."""
    from . import engine

    argv = sys.argv[1:] if argv is None else argv
    root = pathlib.Path(argv[0]) if argv else \
        pathlib.Path(__file__).resolve().parent.parent.parent
    findings = engine.run_passes(root, passes=[BroadExceptPass()])
    # a file the linter cannot parse is a failure here too (the legacy
    # behavior counted the syntax error as an offender)
    failures = [f"{f.path}:{f.line}: {f.message}" for f in findings]
    if failures:
        print(f"{len(failures)} broad exception handler(s) found — "
              f"narrow the exception types (see resilience/retry.py "
              f"for the transient-failure pattern), or justify with a "
              f"'# {PRAGMA} — <reason>' pragma:")
        for f in failures:
            print(" ", f)
        return 1
    print("lint_excepts: OK")
    return 0
