"""RCP2xx — recompile-hazard pass.

The paged/serving plane's whole performance story rests on the
program-ladder discipline: a FIXED set of jitted programs keyed by
bucketed shapes, zero off-ladder compiles under traffic (the hazard
PAPERS.md 2603.09555 designs its O(1) caching around, and what
``obs.compilewatch`` measures at runtime).  This pass flags the three
static shapes that defeat it:

- RCP201  ``jax.jit(...)`` called inside a loop or a per-request
  serving method — every call builds a fresh Python callable with its
  own compile cache, so the XLA cache is defeated by construction.
  Build jitted programs once (``__init__`` / a ``_make_*`` factory /
  module scope) and dispatch to them.
- RCP202  jit over a closure that captures ``self`` (``jax.jit`` of a
  bound method, a ``lambda`` mentioning ``self``, or ``@jit`` directly
  on a method): the captured object is invisible to the trace cache, so
  mutating it silently serves STALE compiled state — and each
  re-creation retraces.  Close over explicit arrays/statics instead.
- RCP203  cache keys interpolating ``.shape`` through an f-string:
  unbucketed shape-derived keys mint a new program per novel shape —
  the off-ladder compile in key form.  Key by the LADDER bucket, not
  the raw shape.

Like every dl4jlint pass this is a reviewer, not a prover: real
must-have sites (e.g. a deliberate per-policy rebuild) carry
``# noqa: RCP20x`` with a justification, and pre-existing accepted
sites live in the baseline.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from .engine import FileContext, Finding, LintPass

# serving-plane method names that sit on the per-request path: creating
# a jitted callable there is a per-request compile by construction
_PER_REQUEST_METHODS = {
    "submit", "submit_many", "generate", "handle", "infer", "predict",
    "do_POST", "do_GET", "step", "decode_step",
}

_SERVING_PREFIX = "deeplearning4j_tpu/serving/"


def _is_jit_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id == "jit"
    return isinstance(f, ast.Attribute) and f.attr == "jit"


def _mentions_self(node: ast.AST) -> bool:
    return any(isinstance(sub, ast.Name) and sub.id == "self"
               for sub in ast.walk(node))


def _mentions_shape(node: ast.AST) -> bool:
    return any(isinstance(sub, ast.Attribute) and sub.attr == "shape"
               for sub in ast.walk(node))


class RecompileHazardPass(LintPass):
    name = "recompile"
    description = ("flag jit-in-loop / jit-over-self / shape-keyed "
                   "cache patterns that defeat the program ladder")
    codes = {
        "RCP201": "jax.jit built inside a loop or per-request method",
        "RCP202": "jit closes over mutable `self` state",
        "RCP203": "cache key interpolates a raw .shape",
    }

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        yield from self._jit_sites(ctx)
        yield from self._shape_keys(ctx)

    # ---- RCP201 / RCP202 --------------------------------------------------

    def _jit_sites(self, ctx: FileContext) -> Iterator[Finding]:
        # walk with an explicit stack so each jit call knows its
        # enclosing loops / function / class
        stack: List[ast.AST] = []

        def visit(node: ast.AST) -> Iterator[Finding]:
            stack.append(node)
            if _is_jit_call(node):
                yield from self._check_jit_call(ctx, node, stack)
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and self._is_method(stack)
                    and any(_is_jit_decorator(d)
                            for d in node.decorator_list)):
                yield Finding(
                    path=ctx.rel, line=node.lineno, col=node.col_offset,
                    code="RCP202", scope=self._scope(stack),
                    symbol=node.name,
                    message=(f"@jit on method `{node.name}` closes over "
                             f"`self` — the trace cache cannot see "
                             f"mutations of the captured object; jit a "
                             f"pure function of explicit args instead"))
            for child in ast.iter_child_nodes(node):
                yield from visit(child)
            stack.pop()

        yield from visit(ctx.tree)

    @staticmethod
    def _is_method(stack: List[ast.AST]) -> bool:
        fn = stack[-1]
        return (isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                and bool(fn.args.args)
                and fn.args.args[0].arg == "self"
                and any(isinstance(n, ast.ClassDef) for n in stack[:-1]))

    @staticmethod
    def _scope(stack: List[ast.AST]) -> str:
        names = [n.name for n in stack
                 if isinstance(n, (ast.ClassDef, ast.FunctionDef,
                                   ast.AsyncFunctionDef))]
        return ".".join(names) if names else "<module>"

    def _check_jit_call(self, ctx: FileContext, node: ast.Call,
                        stack: List[ast.AST]) -> Iterator[Finding]:
        scope = self._scope(stack[:-1])
        # enclosing loop (for/while/comprehension) BELOW the nearest
        # enclosing function boundary:
        # a jit inside `def make(): for ...: jit(...)` is in the loop;
        # a def nested inside a loop builds once per call, not per
        # iteration of the outer loop
        in_loop = False
        for n in reversed(stack[:-1]):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                break
            if isinstance(n, (ast.For, ast.While, ast.ListComp,
                              ast.SetComp, ast.DictComp,
                              ast.GeneratorExp)):
                in_loop = True
                break
        fn = next((n for n in reversed(stack[:-1])
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))), None)
        per_request = (ctx.rel.startswith(_SERVING_PREFIX)
                       and fn is not None
                       and fn.name in _PER_REQUEST_METHODS)
        if in_loop or per_request:
            where = ("a loop" if in_loop
                     else f"per-request method `{fn.name}`")
            yield Finding(
                path=ctx.rel, line=node.lineno, col=node.col_offset,
                code="RCP201", scope=scope, symbol="jit",
                message=(f"jax.jit built inside {where}: each call is "
                         f"a fresh callable with a cold compile cache "
                         f"— hoist it to __init__ / a _make_* factory "
                         f"and reuse the program"))
        # RCP202: the jitted function itself captures self
        target = node.args[0] if node.args else None
        if target is not None:
            captures = (
                (isinstance(target, ast.Lambda)
                 and _mentions_self(target))
                or (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self"))
            if captures:
                yield Finding(
                    path=ctx.rel, line=node.lineno, col=node.col_offset,
                    code="RCP202", scope=scope, symbol="jit",
                    message=("jit over a closure capturing `self` — "
                             "mutations of the captured object are "
                             "invisible to the trace cache (stale "
                             "programs) and every rebuild retraces; "
                             "pass state as explicit arguments"))

    # ---- RCP203 -----------------------------------------------------------

    def _shape_keys(self, ctx: FileContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            joined = None
            # key = f"...{x.shape}..."  (target name mentions "key")
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.JoinedStr):
                if any(isinstance(t, ast.Name) and "key" in t.id.lower()
                       or (isinstance(t, ast.Attribute)
                           and "key" in t.attr.lower())
                       for t in node.targets):
                    joined = node.value
            # cache[f"...{x.shape}..."] / cache.get(f"...") /
            # cache.setdefault(f"...")
            elif isinstance(node, ast.Subscript) and isinstance(
                    node.slice, ast.JoinedStr):
                joined = node.slice
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("get", "setdefault")
                    and node.args
                    and isinstance(node.args[0], ast.JoinedStr)):
                joined = node.args[0]
            if joined is not None and _mentions_shape(joined):
                yield Finding(
                    path=ctx.rel, line=joined.lineno,
                    col=joined.col_offset, code="RCP203",
                    scope="<module>", symbol="shape-key",
                    message=("cache key interpolates a raw `.shape`: "
                             "every novel shape mints a new program "
                             "(the off-ladder compile) — key by the "
                             "bucket ladder instead"))


def _is_jit_decorator(dec: ast.AST) -> bool:
    if isinstance(dec, ast.Name):
        return dec.id == "jit"
    if isinstance(dec, ast.Attribute):
        return dec.attr == "jit"
    if isinstance(dec, ast.Call):
        return _is_jit_decorator(dec.func)
    return False
