"""dl4jlint: first-party static analysis for deeplearning4j_tpu.

A stdlib-only, AST-based lint framework (ISSUE-11) generalizing the
pattern `tools/lint_excepts.py` proved: a bespoke pass in tier-1 keeps a
whole bug class extinct.  Four passes ship today:

- ``pass_locks``    (LCK1xx) — lock-discipline race detector
- ``pass_jit``      (JIT1xx) — host-sync / purity inside jitted code
- ``pass_recompile``(RCP2xx) — program-ladder recompile hazards
- ``pass_excepts``  (BLE0xx) — broad exception handlers

``python -m tools.dl4jlint`` runs them all against the package; any
finding not frozen in ``lint_baseline.json`` fails (and fails tier-1 via
tests/test_lint.py).  See docs/static-analysis.md.
"""

from .engine import (  # noqa: F401
    Finding,
    FileContext,
    LintPass,
    default_passes,
    run_passes,
    load_baseline,
    baseline_counts,
    new_findings,
    render_baseline,
    BASELINE_PATH,
)
