"""``python -m tools.dl4jlint`` — run the lint passes against the tree.

Exit 0 when every finding is frozen in the baseline, 1 otherwise.

    python -m tools.dl4jlint                  # all passes, baselined
    python -m tools.dl4jlint --select locks   # one pass (or code prefix)
    python -m tools.dl4jlint --json           # machine-readable findings
    python -m tools.dl4jlint --no-baseline    # raw findings, no freeze
    python -m tools.dl4jlint --baseline-update  # rewrite the freeze file
    python -m tools.dl4jlint --list-passes
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

from . import engine


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.dl4jlint",
        description="first-party static analysis for deeplearning4j_tpu")
    p.add_argument("root", nargs="?", default=None,
                   help="repo root (default: the checkout this file "
                        "lives in)")
    p.add_argument("--select", action="append", default=None,
                   metavar="PASS|CODE",
                   help="comma-separated pass names or code prefixes "
                        "(locks, jit, recompile, excepts, LCK, JIT101, "
                        "...); repeatable")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit findings as JSON on stdout")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help=f"baseline file (default: {engine.BASELINE_PATH})")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--baseline-update", action="store_true",
                   help="rewrite the baseline from the current tree "
                        "(sorted, diff-friendly) and exit 0")
    p.add_argument("--list-passes", action="store_true",
                   help="print the pass/code catalog and exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    root = (pathlib.Path(args.root).resolve() if args.root
            else pathlib.Path(__file__).resolve().parents[2])
    passes = engine.default_passes()

    if args.list_passes:
        for p in passes:
            print(f"{p.name}: {p.description}")
            for code, desc in sorted(p.codes.items()):
                print(f"  {code}  {desc}")
        return 0

    select = None
    if args.select:
        select = [s for chunk in args.select for s in chunk.split(",")]
    try:
        findings = engine.run_passes(root, passes=passes, select=select)
    except ValueError as e:
        print(f"dl4jlint: {e}", file=sys.stderr)
        return 2

    baseline_path = (pathlib.Path(args.baseline) if args.baseline
                     else engine.BASELINE_PATH)
    if args.baseline_update:
        if select:
            print("--baseline-update refuses --select: the baseline "
                  "freezes the FULL pass set", file=sys.stderr)
            return 2
        baseline_path.write_text(engine.render_baseline(findings))
        print(f"dl4jlint: baseline updated "
              f"({len(findings)} finding(s) frozen) -> {baseline_path}")
        return 0

    if args.no_baseline:
        new = list(findings)
    else:
        new = engine.new_findings(findings,
                                  engine.load_baseline(baseline_path))

    if args.as_json:
        print(json.dumps({
            "root": str(root),
            "total": len(findings),
            "new": [{"path": f.path, "line": f.line, "col": f.col,
                     "code": f.code, "scope": f.scope,
                     "symbol": f.symbol, "message": f.message,
                     "key": f.key}
                    for f in new]}, indent=2))
        return 1 if new else 0

    if new:
        print(f"dl4jlint: {len(new)} new finding(s) "
              f"({len(findings)} total, "
              f"{len(findings) - len(new)} baselined) — fix them or "
              f"justify with a `# noqa: <CODE> — reason` pragma "
              f"(docs/static-analysis.md):")
        for f in new:
            print(" ", f.render())
        return 1
    print(f"dl4jlint: OK ({len(findings)} baselined finding(s), 0 new)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
