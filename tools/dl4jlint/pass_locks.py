"""LCK1xx — lock-discipline race detector.

The serving/fleet/supervisor/obs planes (ISSUEs 4-10) put every shared
mutable field behind an instance lock, and the runtime chaos tests
assert the resulting ledger invariants — but nothing checked that a NEW
field access actually lands under the lock.  This pass is the static
shadow of those invariants:

For each class (in ``serving/``, ``obs/``, ``resilience/``,
``runtime/launcher.py``) that creates a ``threading.Lock``/``RLock``/
``Condition``, infer the **guarded map**: for every ``self._x``
attribute, the set of locks it is written under inside
``with self.<lock>:`` blocks.  Then flag any access to a guarded
attribute that holds none of its owning locks (LCK101) — outside every
lock, or under the WRONG lock of a multi-lock class; both are exactly
the shape of a torn read / lost update once a second thread exists.

Deliberate blind spots (kept small and documented):

- ``__init__`` is exempt — construction is single-threaded by contract.
- Methods whose name ends in ``_locked`` are exempt — the repo-wide
  convention for "caller holds the lock" helpers.
- Attributes only ever touched outside locks never enter the guarded
  set, so lock-free config fields (set once in ``__init__``) are quiet.
- ``lock.acquire()/release()`` pairs are not modeled; the codebase uses
  ``with`` exclusively, and a raw acquire is itself worth flagging by
  eye in review.
- Nested defs/lambdas are scanned with NO locks held: they run later,
  on whatever thread calls them — the enclosing ``with`` guards their
  construction, not their body.

False positives (a field genuinely safe outside the lock — e.g. written
only before the worker thread starts) carry ``# noqa: LCK101`` with a
one-line justification, the same contract as BLE001.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from .engine import FileContext, Finding, LintPass

# where the lock discipline is load-bearing (threads exist at scale)
INCLUDE_PREFIXES = (
    "deeplearning4j_tpu/serving/",
    "deeplearning4j_tpu/obs/",
    "deeplearning4j_tpu/resilience/",
    "deeplearning4j_tpu/runtime/launcher.py",
)

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}


def _is_lock_ctor(node: ast.AST) -> bool:
    """threading.Lock() / Lock() / threading.Condition(lock) ..."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in _LOCK_FACTORIES
    if isinstance(f, ast.Attribute):
        return f.attr in _LOCK_FACTORIES
    return False


def _self_attr(node: ast.AST):
    """'x' when node is `self.x`, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


# container mutations count as WRITES: the serving plane's shared state
# is mostly deques/dicts/lists (`self._queue.append`, `.popleft()`,
# `self._table[k] = v`), and rebinding-only modeling would exclude
# exactly that dominant shape from the race detector
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "clear", "add", "discard", "update",
    "setdefault", "sort", "reverse",
}


class _MethodScanner(ast.NodeVisitor):
    """Record every `self.<attr>` access in one method with WHICH locks
    are lexically held at the access site (`with self.<lock>:` nesting).
    Accesses: (attr, lineno, col, held_locks_frozenset, is_write).
    Writes are rebinds (`self._x = ...`), subscript stores
    (`self._x[k] = v`) and known mutator calls (`self._x.append(...)`).
    """

    def __init__(self, lock_attrs: Set[str]):
        self.lock_attrs = lock_attrs
        self.held: List[str] = []            # with-lock nesting, by name
        self.accesses: List[Tuple[str, int, int, frozenset, bool]] = []
        self._write_sites: Set[Tuple[int, int]] = set()

    def visit_With(self, node: ast.With) -> None:
        taken = [a for item in node.items
                 if (a := _self_attr(item.context_expr)) in self.lock_attrs]
        for item in node.items:
            self.visit(item)
        self.held.extend(taken)
        for stmt in node.body:
            self.visit(stmt)
        del self.held[len(self.held) - len(taken):len(self.held)]

    def _record(self, attr: str, node: ast.AST, is_write: bool) -> None:
        if is_write:
            self._write_sites.add((node.lineno, node.col_offset))
        self.accesses.append((attr, node.lineno, node.col_offset,
                              frozenset(self.held), is_write))

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            attr = _self_attr(f.value)
            if attr is not None and attr not in self.lock_attrs:
                # recorded at the `self._x` position so the inner
                # Attribute visit below dedupes against it
                self._record(attr, f.value, True)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = _self_attr(node.value)
            if attr is not None and attr not in self.lock_attrs:
                self._record(attr, node.value, True)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if (attr is not None and attr not in self.lock_attrs
                and (node.lineno, node.col_offset)
                not in self._write_sites):
            self._record(attr, node,
                         isinstance(node.ctx, (ast.Store, ast.Del)))
        self.generic_visit(node)

    def _visit_deferred(self, node: ast.AST) -> None:
        # a nested def/lambda runs LATER, on whatever thread calls it —
        # the lexically enclosing `with self._lock:` guards its
        # construction, not its body.  Scan the body with no locks
        # held, so a deferred write can neither hide a race nor grant
        # false lock ownership to the guarded map.
        saved, self.held = self.held, []
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.held = saved

    def visit_FunctionDef(self, node) -> None:
        self._visit_deferred(node)

    def visit_AsyncFunctionDef(self, node) -> None:
        self._visit_deferred(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._visit_deferred(node)


class LockDisciplinePass(LintPass):
    name = "locks"
    description = ("flag reads/writes of lock-guarded `self._x` fields "
                   "outside the lock")
    codes = {"LCK101": "guarded attribute accessed outside its lock"}

    def run(self, ctx: FileContext) -> Iterator[Finding]:
        if not ctx.rel.startswith(INCLUDE_PREFIXES):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(ctx, node)

    # ---- per-class --------------------------------------------------------

    def _lock_attrs(self, cls: ast.ClassDef):
        """(locks, alias) for the class: `locks` is every self attribute
        assigned a Lock/RLock/Condition (plain or annotated assign);
        `alias` maps a Condition built OVER another lock to that lock
        (`self._cond = threading.Condition(self._lock)` — holding either
        IS holding the one underlying lock, so wrong-lock analysis must
        not treat them as distinct)."""
        locks: Set[str] = set()
        alias: Dict[str, str] = {}
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
                targets = node.targets
            elif (isinstance(node, ast.AnnAssign)   # typed style:
                    and node.value is not None      # self._lock: Lock = ...
                    and _is_lock_ctor(node.value)):
                targets = [node.target]
            else:
                continue
            wraps = None
            if node.value.args:
                wraps = _self_attr(node.value.args[0])
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is not None:
                    locks.add(attr)
                    if wraps is not None:
                        alias[attr] = wraps
        # canonicalize chains once (Condition-over-Condition is absurd
        # but cheap to handle)
        for a in list(alias):
            seen = {a}
            while alias.get(alias[a]) is not None and alias[a] not in seen:
                seen.add(alias[a])
                alias[a] = alias[alias[a]]
        return locks, alias

    def _check_class(self, ctx: FileContext,
                     cls: ast.ClassDef) -> Iterator[Finding]:
        locks, alias = self._lock_attrs(cls)
        if not locks:
            return
        canon = lambda h: alias.get(h, h)   # noqa: E731
        # a LIST of (name, accesses) — not a dict — so same-named defs
        # (property getter/setter pairs) each keep their own entry
        per_method: List[Tuple[str, List[Tuple[str, int, int, frozenset,
                                               bool]]]] = []
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            scanner = _MethodScanner(locks)
            for s in stmt.body:
                scanner.visit(s)
            per_method.append((stmt.name, scanner.accesses))
        # guarded maps attr -> the set of locks it is WRITTEN under
        # (outside __init__): the class's own declaration of "this field
        # is mutable shared state, owned by THESE locks".  Fields only
        # ever read (config set once at construction) never enter,
        # however often a locked block happens to read them.  Tracking
        # the owning locks (not just "any lock") also catches the
        # wrong-lock race: a field guarded by `_b` read under `_a` is
        # as torn as one read under no lock at all.
        guarded: Dict[str, Set[str]] = {}
        for method, accesses in per_method:
            if method == "__init__":
                continue
            for attr, _ln, _col, held, is_write in accesses:
                if held and is_write:
                    guarded.setdefault(attr, set()).update(
                        canon(h) for h in held)
        if not guarded:
            return
        for method, accesses in per_method:
            if method == "__init__" or method.endswith("_locked"):
                continue
            for attr, lineno, col, held, is_write in accesses:
                owners = guarded.get(attr)
                if owners is None or {canon(h) for h in held} & owners:
                    continue
                kind = "written" if is_write else "read"
                where = ("under " + "/".join(
                    f"`self.{h}`" for h in sorted(held)) + " only"
                    if held else "outside the lock")
                owner = "/".join(f"self.{o}" for o in sorted(owners))
                yield Finding(
                    path=ctx.rel, line=lineno, col=col,
                    code="LCK101",
                    scope=f"{cls.name}.{method}",
                    symbol=attr,
                    message=(f"`self.{attr}` {kind} {where}, but it "
                             f"is guarded by {owner} elsewhere in "
                             f"{cls.name} — take that lock, rename "
                             f"the helper `*_locked`, or justify "
                             f"with `# noqa: LCK101`"))
