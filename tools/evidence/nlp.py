"""NLP-tier evidence: Word2Vec similarity structure, GloVe co-occurrence
training, ParagraphVectors doc inference, and the out-of-the-box POS
tagger — the L5 stack (`Word2Vec.java`, `Glove.java:60`,
`ParagraphVectors.java:61`, `PoStagger.java:248`) on real sentences."""

from _common import capture, ensure_cpu_mesh, write_log

ensure_cpu_mesh(8)

import numpy as np  # noqa: E402

TECH = ["cpu", "gpu", "tpu", "chip", "cache", "kernel", "tensor", "shard"]
FRUIT = ["apple", "banana", "mango", "pear", "grape", "plum", "peach",
         "melon"]


def corpus(n=400, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        pool = TECH if rng.random() < 0.5 else FRUIT
        out.append(" ".join(rng.choice(pool, size=8)))
    return out


def main() -> None:
    sents = corpus()

    print("== leg 1: Word2Vec topic structure (negative sampling)")
    from deeplearning4j_tpu.nlp.word2vec import Word2Vec

    w2v = Word2Vec(vector_length=24, window=3, epochs=5, seed=1,
                   negative=5, batch_size=512, learning_rate=0.025)
    w2v.fit(sents)
    within = w2v.similarity("apple", "banana")
    across = w2v.similarity("apple", "gpu")
    print(f"within-topic sim {within:.3f} vs cross-topic {across:.3f}")
    assert within > across + 0.2
    print("words_nearest('cpu'):", w2v.words_nearest("cpu", top_n=4))

    print("== leg 2: GloVe on the same corpus")
    from deeplearning4j_tpu.nlp.glove import Glove

    gl = Glove(vector_length=24, window=3, epochs=8, seed=1)
    gl.fit(sents)
    gw = gl.similarity("apple", "banana")
    ga = gl.similarity("apple", "gpu")
    print(f"glove within {gw:.3f} vs cross {ga:.3f}")
    assert gw > ga

    print("== leg 3: ParagraphVectors DBOW + infer")
    from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors

    labels = [f"doc{i}" for i in range(60)]
    docs = corpus(60, seed=7)
    pv = ParagraphVectors(vector_length=24, window=3, epochs=5, seed=2,
                          negative=5, batch_size=512)
    pv.fit_labelled(docs, labels)
    vec = pv.infer_vector(docs[0].split())
    print("infer_vector shape:", np.asarray(vec).shape)
    assert np.isfinite(np.asarray(vec)).all()

    print("== leg 4: out-of-the-box POS tagger (embedded seed corpus)")
    from deeplearning4j_tpu.nlp.annotators import default_tagger

    tags = default_tagger().tag_text(
        "The quick network trains a deep model .")
    print("tags:", tags)
    assert ("The", "DET") in tags and ("trains", "VERB") in tags
    print("GREEN: NLP stack (w2v, glove, paragraph vectors, tagger)")


if __name__ == "__main__":
    with capture() as buf:
        main()
    write_log("nlp", buf.getvalue())
