"""Hybrid-parallel training evidence: the SAME byte-LM task trained to
decreasing loss on BOTH multichip layouts — dp/sp/tp (GSPMD + ring
attention) on a (2,2,2) mesh and dp/pp (GPipe scan+ppermute) on a (2,4)
mesh — with a single-device oracle trained on identical data for
comparison.  One-step parity lives in tests/test_parallel_extended.py
and the dryrun logs; this artifact shows real multi-step optimization
on both meshes (`SparkDl4jMultiLayer.java:182-202` and the Akka tier
are the reference stakes; the mesh layouts are the TPU-first redesign)."""

from _common import capture, ensure_cpu_mesh, write_log

ensure_cpu_mesh(8)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from deeplearning4j_tpu.parallel import make_mesh  # noqa: E402
from deeplearning4j_tpu.parallel import transformer as tfm  # noqa: E402
from deeplearning4j_tpu.parallel.hybrid import (  # noqa: E402
    HybridParallelTrainer,
    PipelineParallelTrainer,
    make_accum_train_step,
)

STEPS = 30


def _data(cfg, n, seed):
    """Byte-LM batches from a repeating structured pattern (learnable)."""
    rng = np.random.default_rng(seed)
    base = np.arange(cfg.max_len) % 17 + 1
    toks = np.stack([np.roll(base, rng.integers(0, 17)) for _ in range(n)])
    tgts = np.roll(toks, -1, axis=1)
    return toks.astype(np.int32), tgts.astype(np.int32)


def main() -> None:
    devs = jax.devices()
    print(f"devices: {len(devs)} ({jax.default_backend()})")
    cfg = tfm.TransformerConfig(vocab_size=32, d_model=32, n_heads=4,
                                n_layers=4, d_ff=64, max_len=16)
    tokens, targets = _data(cfg, 8, seed=1)

    print(f"== single-device Adam oracle, {STEPS} steps")
    step, init_state = make_accum_train_step(cfg, lr=3e-3, accum=1,
                                             updater="adam")
    p = tfm.init_params(cfg, jax.random.PRNGKey(3))
    state = init_state(p)
    tok_d, tgt_d = jnp.asarray(tokens), jnp.asarray(targets)
    oracle = []
    for _ in range(STEPS):
        p, state, loss = step(p, state, tok_d, tgt_d)
        oracle.append(float(loss))
    print(f"oracle loss: {oracle[0]:.4f} -> {oracle[-1]:.4f}")

    print(f"== dp/sp/tp mesh=(2,2,2), Adam, {STEPS} steps")
    mesh = make_mesh((2, 2, 2), ("data", "seq", "model"), devices=devs[:8])
    tr = HybridParallelTrainer(cfg, mesh, lr=3e-3, seed=3, updater="adam")
    h_losses = [tr.fit_batch(tokens, targets) for _ in range(STEPS)]
    print(f"hybrid loss: {h_losses[0]:.4f} -> {h_losses[-1]:.4f} "
          f"(matches oracle to "
          f"{max(abs(a - b) for a, b in zip(h_losses, oracle)):.1e})")
    assert h_losses[-1] < h_losses[0] * 0.8

    print(f"== dp/pp mesh=(2,4), GPipe microbatches=2, Adam, {STEPS} steps")
    mesh_pp = make_mesh((2, 4), ("data", "stage"), devices=devs[:8])
    tr_pp = PipelineParallelTrainer(cfg, mesh_pp, n_microbatches=2,
                                    lr=3e-3, seed=3, updater="adam")
    p_losses = [tr_pp.fit_batch(tokens, targets) for _ in range(STEPS)]
    print(f"pipeline loss: {p_losses[0]:.4f} -> {p_losses[-1]:.4f} "
          f"(matches oracle to "
          f"{max(abs(a - b) for a, b in zip(p_losses, oracle)):.1e})")
    assert p_losses[-1] < p_losses[0] * 0.8
    print("GREEN: both multichip layouts train the same task to "
          "decreasing loss, tracking the single-device oracle")


if __name__ == "__main__":
    with capture() as buf:
        main()
    write_log("hybrid_training", buf.getvalue())
