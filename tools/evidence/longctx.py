"""Ring-flash long-context evidence: S=4096 sharded 8 ways on the
virtual CPU mesh, fwd+bwd vs the dense oracle, with wall timings.

Proves the SURVEY §5 long-context extension at a length where blocking
and ring scheduling actually engage (the 2015 reference's long-sequence
story is one LSTM scanning timesteps, `GravesLSTM.java:108`)."""

from _common import capture, ensure_cpu_mesh, write_log

ensure_cpu_mesh(8)

import time  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

from deeplearning4j_tpu.parallel import make_mesh  # noqa: E402
from deeplearning4j_tpu.parallel.data_parallel import shard_map  # noqa: E402
from deeplearning4j_tpu.parallel.ring_attention import (  # noqa: E402
    attention,
    ring_flash_attention,
)


def main() -> None:
    B, S, H, D, N = 1, 4096, 2, 32, 8
    print(f"devices: {len(jax.devices())} ({jax.default_backend()}); "
          f"B={B} S={S} H={H} D={D}, seq sharded {N} ways "
          f"(S_local={S // N})")
    rng = np.random.default_rng(11)
    q, k, v = (jnp.asarray(rng.standard_normal((B, S, H, D)), jnp.float32)
               for _ in range(3))
    mesh = make_mesh((N,), ("seq",), devices=jax.devices()[:N])
    ring = shard_map(
        lambda q, k, v: ring_flash_attention(q, k, v, "seq", causal=True),
        mesh=mesh, in_specs=(P(None, "seq"),) * 3,
        out_specs=P(None, "seq"), check_rep=False)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    jr = jax.jit(jax.value_and_grad(loss_ring, (0, 1, 2)))
    jd = jax.jit(jax.value_and_grad(loss_dense, (0, 1, 2)))
    t0 = time.perf_counter()
    lr_, gr = jax.block_until_ready(jr(q, k, v))
    print(f"ring-flash fwd+bwd compile+run: {time.perf_counter() - t0:.1f}s")
    t0 = time.perf_counter()
    ld_, gd = jax.block_until_ready(jd(q, k, v))
    print(f"dense oracle fwd+bwd compile+run: {time.perf_counter() - t0:.1f}s")
    for name, fn in (("ring-flash", jr), ("dense", jd)):
        t0 = time.perf_counter()
        for _ in range(3):
            out = fn(q, k, v)
        jax.block_until_ready(out)
        print(f"{name} steady fwd+bwd: {(time.perf_counter() - t0) / 3 * 1e3:.0f} ms")
    err_f = float(jnp.max(jnp.abs(lr_ - ld_)) / jnp.maximum(jnp.abs(ld_), 1))
    err_g = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(gr, gd))
    print(f"loss rel err: {err_f:.2e}; max grad abs err: {err_g:.2e}")
    assert err_f < 1e-5 and err_g < 5e-4, (err_f, err_g)
    print("GREEN: ring-flash @S=4096 sharded 8 ways matches the dense "
          "oracle fwd+bwd")

    # Leg 2: S=16384 — the bench_longctx length.  A global dense oracle
    # would materialize [16384, 16384] scores, so the reference here is
    # the ring schedule with the DENSE per-hop inner (exact blockwise
    # softmax-merge), which the flash inner must match.
    from deeplearning4j_tpu.parallel.ring_attention import ring_attention

    S2 = 16384
    q2, k2, v2 = (jnp.asarray(
        rng.standard_normal((1, S2, 2, 32)), jnp.float32) for _ in range(3))

    def make(fn):
        return jax.jit(shard_map(
            lambda q, k, v: fn(q, k, v, "seq", causal=True), mesh=mesh,
            in_specs=(P(None, "seq"),) * 3, out_specs=P(None, "seq"),
            check_rep=False))

    rf, rd = make(ring_flash_attention), make(ring_attention)
    t0 = time.perf_counter()
    out_f = jax.block_until_ready(rf(q2, k2, v2))
    tf = time.perf_counter() - t0
    t0 = time.perf_counter()
    out_d = jax.block_until_ready(rd(q2, k2, v2))
    td = time.perf_counter() - t0
    err = float(jnp.max(jnp.abs(out_f - out_d)))
    print(f"S=16384 fwd: ring-flash {tf:.1f}s vs ring-dense {td:.1f}s "
          f"(incl. compile); max abs err {err:.2e}")
    assert err < 5e-5, err
    print("GREEN: ring-flash @S=16384 (bench length) matches the exact "
          "ring schedule")


if __name__ == "__main__":
    with capture() as buf:
        main()
    write_log("longctx", buf.getvalue())
