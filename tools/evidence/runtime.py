"""Runtime-tier evidence: (1) elastic resume — train DP on 8 devices
with Adam, checkpoint params + updater moments, lose half the slice,
resume on 4 bit-exactly; (2) torch Sequential import with logit parity
(the dl4j-caffe stub's model-import role)."""

from _common import capture, ensure_cpu_mesh, write_log

ensure_cpu_mesh(8)

import pathlib  # noqa: E402
import tempfile  # noqa: E402

import jax  # noqa: E402
import numpy as np  # noqa: E402

from deeplearning4j_tpu.models import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.nn.conf import (  # noqa: E402
    DenseLayerConf,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OutputLayerConf,
)
from deeplearning4j_tpu.parallel import make_mesh  # noqa: E402
from deeplearning4j_tpu.parallel.data_parallel import (  # noqa: E402
    DataParallelTrainer,
)
from deeplearning4j_tpu.runtime.checkpoint import (  # noqa: E402
    load_checkpoint,
    save_checkpoint,
)


def main() -> None:
    print(f"devices: {len(jax.devices())} ({jax.default_backend()})")
    print("== leg 1: elastic resume 8 -> 4 devices (Adam moments survive)")
    conf = MultiLayerConfiguration(
        conf=NeuralNetConfiguration(learning_rate=0.01, updater="adam"),
        layers=(DenseLayerConf(n_in=8, n_out=16, activation="tanh"),
                OutputLayerConf(n_in=16, n_out=4)))
    rng = np.random.default_rng(3)
    X = rng.standard_normal((256, 8)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 256)]
    net = MultiLayerNetwork(conf).init()
    big = DataParallelTrainer(net, mesh=make_mesh((8,), ("data",)))
    for _ in range(5):
        big.fit_batch(X, Y)
    ckdir = pathlib.Path(tempfile.mkdtemp())
    save_checkpoint(ckdir, step=5, params=net.params,
                    updater_state=net.updater_state)
    loss_big6 = float(big.fit_batch(X, Y))
    print(f"8-dev step-6 loss: {loss_big6:.5f}; checkpoint saved at step 5")
    net2 = MultiLayerNetwork(conf).init()
    step, params, upd, _ = load_checkpoint(
        ckdir, net2.params, updater_like=net2.updater_state)
    net2.params, net2.updater_state = params, upd
    small = DataParallelTrainer(
        net2, mesh=make_mesh((4,), ("data",), devices=jax.devices()[:4]))
    loss_small6 = float(small.fit_batch(X, Y))
    print(f"resume at step {step} on 4 devices: step-6 loss "
          f"{loss_small6:.5f} (delta vs 8-dev "
          f"{abs(loss_small6 - loss_big6):.2e})")
    assert abs(loss_small6 - loss_big6) < 1e-3
    tail = [float(small.fit_batch(X, Y)) for _ in range(10)]
    print(f"continues converging on the smaller mesh: "
          f"{tail[0]:.5f} -> {tail[-1]:.5f}")
    assert tail[-1] < tail[0]

    print("== leg 2: torch Sequential import, logit parity")
    import torch
    import torch.nn as tnn

    from deeplearning4j_tpu.runtime.model_import import (
        import_torch_sequential,
    )

    tm = tnn.Sequential(tnn.Linear(8, 32), tnn.ReLU(),
                        tnn.Linear(32, 4), tnn.Softmax(dim=-1))
    inet, report = import_torch_sequential(tm)
    print("conversion report:", report)
    xt = torch.randn(16, 8)
    with torch.no_grad():
        ref = tm(xt).numpy()
    got = np.asarray(inet.output(xt.numpy()))
    err = float(np.max(np.abs(got - ref)))
    print(f"imported-net output max abs err vs torch: {err:.2e}")
    assert err < 1e-5
    print("GREEN: elastic resume + torch import")


if __name__ == "__main__":
    with capture() as buf:
        main()
    write_log("runtime", buf.getvalue())
