"""Flagship LM path end-to-end as a user drives it: `dl4j lm` trains a
byte-level TransformerLM on the repo's own docs, saves, scores held-out
perplexity, and samples — one CLI invocation, real text."""

import subprocess
import sys
import tempfile

from _common import REPO, capture, ensure_cpu_mesh, write_log

ensure_cpu_mesh(8)


def main() -> None:
    docs = sorted((REPO / "docs").glob("*.md")) + [
        REPO / "README.md", REPO / "PARITY.md", REPO / "BASELINE.md",
        REPO / "SURVEY.md"]
    data = b"".join(p.read_bytes() for p in docs if p.exists())
    cut = int(len(data) * 0.9)
    tmp = tempfile.mkdtemp()
    train, heldout = f"{tmp}/train.txt", f"{tmp}/heldout.txt"
    open(train, "wb").write(data[:cut])
    open(heldout, "wb").write(data[cut:])
    print(f"corpus: {cut} train bytes / {len(data) - cut} held-out bytes "
          f"from {len(docs)} repo docs")
    cmd = [sys.executable, "-m", "deeplearning4j_tpu.cli", "lm",
           "-input", train, "-output", f"{tmp}/lm", "-epochs", "3",
           "-batch", "8", "-seq", "128", "-d-model", "128", "-layers", "3",
           "-heads", "4", "-lr", "3e-3", "-updater", "adam",
           "-eval", heldout, "-generate", "The TPU", "-max-new", "120",
           "-temperature", "0.8", "-top-k", "40", "-verbose"]
    print("command:", " ".join(cmd[1:]))
    proc = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                          timeout=1800)
    out = proc.stdout + proc.stderr
    for line in out.splitlines():
        if "Platform" not in line:
            print(line)
    assert proc.returncode == 0, proc.returncode
    assert "perplexity" in out
    print("GREEN: dl4j lm train -> save -> eval -> generate")


if __name__ == "__main__":
    with capture() as buf:
        main()
    write_log("lm_cli", buf.getvalue())
