"""Profiling evidence: a real XLA trace captured around training steps,
StepTimer throughput stats, and device memory stats — the §5 profiling
subsystem (beyond the 2015 reference, which had no profiler)."""

from _common import capture, ensure_cpu_mesh, write_log

ensure_cpu_mesh(8)

import pathlib  # noqa: E402
import tempfile  # noqa: E402

import numpy as np  # noqa: E402

from deeplearning4j_tpu.models import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.nn.conf import (  # noqa: E402
    DenseLayerConf,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OutputLayerConf,
)
from deeplearning4j_tpu.runtime.profiler import (  # noqa: E402
    StepTimer,
    annotate,
    device_memory_stats,
    trace,
)


def main() -> None:
    conf = MultiLayerConfiguration(
        conf=NeuralNetConfiguration(learning_rate=0.05, updater="adam"),
        layers=(DenseLayerConf(n_in=32, n_out=64, activation="relu"),
                OutputLayerConf(n_in=64, n_out=4)))
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    X = rng.standard_normal((128, 32)).astype(np.float32)
    Y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 128)]

    logdir = tempfile.mkdtemp()
    timer = StepTimer(batch_size=128, skip=1)  # iteration listener
    with trace(logdir):
        for i in range(6):
            with annotate(f"step{i}"):
                net.fit_batch(X, Y)
            timer(i, 0.0)
    files = list(pathlib.Path(logdir).rglob("*"))
    traced = [f for f in files if f.is_file()]
    print(f"trace artifacts written: {len(traced)} files "
          f"(e.g. {traced[0].name if traced else 'none'})")
    assert traced, "no trace files written"
    stats = timer.summary()
    print("StepTimer:", {k: round(v, 2) if isinstance(v, float) else v
                         for k, v in stats.items()})
    assert stats["steps"] == 4 and stats["examples_per_sec"] > 0
    mem = device_memory_stats()
    print(f"device_memory_stats: {len(mem)} device entries "
          f"(keys: {sorted(mem[0])[:4] if mem else '-'})")
    print("GREEN: profiling subsystem (trace, StepTimer, memory stats)")


if __name__ == "__main__":
    with capture() as buf:
        main()
    write_log("profiling", buf.getvalue())
