"""UI server evidence: every endpoint exercised over real HTTP —
t-SNE upload+generate, VP-tree nearest neighbors, weight/activation
histograms, the dashboard page, and KV-cached LM generation (sampled
and beam) from a registered TransformerLM.

Reference role: `UiServer.java:58` (coords/t-SNE/NN/weights/activations)
plus LM serving the 2015 reference never had."""

from _common import capture, ensure_cpu_mesh, write_log

ensure_cpu_mesh(8)

import dataclasses  # noqa: E402
import json  # noqa: E402
import urllib.request  # noqa: E402

import jax  # noqa: E402
import numpy as np  # noqa: E402

from deeplearning4j_tpu.parallel import transformer as tfm  # noqa: E402
from deeplearning4j_tpu.ui.server import UiServer  # noqa: E402


def main() -> None:
    srv = UiServer(port=0).start()
    base = srv.url

    def post(path, payload):
        req = urllib.request.Request(
            base + path, json.dumps(payload).encode(),
            {"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(req, timeout=300).read())

    def get(path):
        return urllib.request.urlopen(base + path, timeout=300).read()

    rng = np.random.default_rng(0)
    X = rng.standard_normal((60, 16)).tolist()
    words = [f"w{i}" for i in range(60)]
    print("tsne/upload:", post("/tsne/upload",
                               {"vectors": X, "labels": words}))
    coords = post("/tsne/generate", {"iterations": 60, "perplexity": 8.0})
    print("tsne/generate: coords", np.asarray(coords["coords"]).shape)
    print("nn/upload:", post("/nearestneighbors/upload",
                             {"vectors": X, "labels": words}))
    nn = post("/nearestneighbors", {"word": "w3", "k": 4})
    print("nearestneighbors(w3) ->",
          [n["label"] for n in nn["neighbors"]])
    print("weights POST:", post("/weights",
                                {"layers": {"dense0": {"W": X}}}))
    print("weights GET bytes:", len(get("/weights")))
    print("activations POST:",
          post("/activations", {"activations": {"dense0": X}}))
    dash = get("/")
    print("dashboard:", len(dash), "bytes, html:",
          b"<html" in dash.lower())
    cfg = dataclasses.replace(
        tfm.gpt2_small(max_len=64), vocab_size=256, d_model=64,
        n_heads=4, n_layers=2, d_ff=128, dtype="float32")
    params = tfm.init_params(cfg, jax.random.PRNGKey(0))
    srv.serve_lm(cfg, params)
    out = post("/lm/generate", {"prompt_ids": [104, 105],
                                "max_new_tokens": 8, "top_k": 5,
                                "temperature": 0.8})
    print("lm/generate ids:", out["ids"])
    beam = post("/lm/generate", {"prompt_ids": [104, 105],
                                 "max_new_tokens": 6, "beam_size": 3})
    print("lm/generate beam:", beam["ids"], "score",
          round(beam["score"], 3))
    greedy = post("/lm/generate", {"prompt_ids": [104, 105],
                                   "max_new_tokens": 6})
    print("lm/generate continuous (slot pool):", greedy["ids"])
    # batched classifier serving (serving/: micro-batcher + bucket ladder)
    from deeplearning4j_tpu.models import MultiLayerNetwork, iris_mlp

    net = MultiLayerNetwork(iris_mlp()).init()
    srv.serve_model(net, max_batch=8,
                    warmup_example=np.zeros((4,), np.float32))
    pred = post("/model/predict", {"features": [[0.1, 0.2, 0.3, 0.4],
                                                [1.0, 0.9, 0.8, 0.7]]})
    print("model/predict:", pred["predictions"])
    stats = json.loads(get("/serving/stats"))
    print("serving/stats: classifier programs",
          stats["classifier"]["compiled_programs"], "| lm slots",
          stats["lm"]["slots"], "tokens", stats["lm"].get("tokens"))
    srv.stop()
    print("GREEN: all UI endpoints served over HTTP")


if __name__ == "__main__":
    with capture() as buf:
        main()
    write_log("ui_server", buf.getvalue())
