"""Shared plumbing for the evidence runners (tools/evidence/*.py).

Each runner proves one subsystem end-to-end and writes a timestamped,
committed log to EVIDENCE/ carrying the git SHA, host fingerprint, and
full output — the artifact class VERDICT r4 asked for ("a committed,
timestamped, reproducible artifact, not prose").  Run them all with
`make evidence`.

Runners force the scrubbed-CPU environment themselves (mirror of
`__graft_entry__.scrub_tpu_env`): when the axon tunnel is wedged, a
fresh python hangs dialing it before any repo code runs, so the
decision must be made from the environment BEFORE jax is imported.
"""

from __future__ import annotations

import contextlib
import io
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent.parent
EVIDENCE = REPO / "EVIDENCE"
if str(REPO) not in sys.path:  # scripts run from tools/evidence/
    sys.path.insert(0, str(REPO))


def ensure_cpu_mesh(n_devices: int = 8) -> None:
    """Re-exec into a scrubbed n-device virtual CPU mesh if needed.

    Mirrors `__graft_entry__.dryrun_multichip`'s parent/child decision:
    made from env alone, before any jax import."""
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    if (os.environ.get("JAX_PLATFORMS") == "cpu"
            and flag in os.environ.get("XLA_FLAGS", "")):
        return
    from __graft_entry__ import scrub_tpu_env

    env = scrub_tpu_env(dict(os.environ), n_devices)
    script = str(pathlib.Path(sys.argv[0]).resolve())
    raise SystemExit(subprocess.run(
        [sys.executable, script, *sys.argv[1:]], env=env,
        cwd=REPO).returncode)


def write_log(name: str, body: str) -> pathlib.Path:
    EVIDENCE.mkdir(exist_ok=True)
    sha = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                         cwd=REPO, capture_output=True,
                         text=True).stdout.strip() or "unknown"
    stamp = time.strftime("%Y%m%d_%H%M", time.gmtime())
    path = EVIDENCE / f"{name}_{stamp}.log"
    head = (f"== {name}  {time.strftime('%a %b %d %H:%M:%S UTC %Y', time.gmtime())}"
            f"  sha={sha}\n"
            f"host: {os.cpu_count()} cpu core(s); "
            f"JAX_PLATFORMS={os.environ.get('JAX_PLATFORMS', '')} "
            f"XLA_FLAGS={os.environ.get('XLA_FLAGS', '')}\n"
            f"command: python {' '.join(sys.argv)}\n")
    path.write_text(head + body)
    print(f"-> {path.relative_to(REPO)}")
    return path


@contextlib.contextmanager
def capture():
    """Tee stdout to both the console and the returned buffer."""
    buf = io.StringIO()
    real = sys.stdout

    class Tee(io.TextIOBase):
        def write(self, s):
            real.write(s)
            buf.write(s)
            return len(s)

        def flush(self):
            real.flush()

    sys.stdout = Tee()
    try:
        yield buf
    finally:
        sys.stdout = real
