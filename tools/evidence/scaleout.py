"""Scaleout evidence: real model training through the distributed job
model — master + 3 workers, param-averaging rounds, model shipped as
(conf-JSON, params) exactly like the reference's universal format
(`MultiLayerNetwork.java:97-101`) — then the same job-grab path over
the HMAC-framed TCP tracker server (the Hazelcast-role transport), then
the reaper recovering an orphaned job (`MasterActor.java:141-160`)."""

from _common import capture, ensure_cpu_mesh, write_log

ensure_cpu_mesh(8)

import time  # noqa: E402

import jax  # noqa: E402
import numpy as np  # noqa: E402

from deeplearning4j_tpu.datasets.fetchers import iris_dataset  # noqa: E402
from deeplearning4j_tpu.models import MultiLayerNetwork  # noqa: E402
from deeplearning4j_tpu.nn.conf import (  # noqa: E402
    DenseLayerConf,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OutputLayerConf,
)
from deeplearning4j_tpu.scaleout.aggregators import (  # noqa: E402
    ParameterAveragingAggregator,
)
from deeplearning4j_tpu.scaleout.performers import NetworkPerformer  # noqa: E402
from deeplearning4j_tpu.scaleout.runner import DistributedRunner  # noqa: E402
from deeplearning4j_tpu.scaleout.statetracker import (  # noqa: E402
    Job,
    StateTracker,
)
from deeplearning4j_tpu.scaleout.tracker_server import (  # noqa: E402
    RemoteStateTracker,
    StateTrackerServer,
)


def main() -> None:
    ds = iris_dataset()
    conf = MultiLayerConfiguration(
        conf=NeuralNetConfiguration(learning_rate=0.05, updater="adam"),
        layers=(DenseLayerConf(n_in=4, n_out=16, activation="relu"),
                OutputLayerConf(n_in=16, n_out=3)))
    conf_json = conf.to_json()
    rng = np.random.default_rng(0)
    X, Y = np.asarray(ds.features), np.asarray(ds.labels)
    batches = []
    for _ in range(30):
        idx = rng.integers(0, len(X), 32)
        batches.append((X[idx], Y[idx]))

    print("== leg 1: iterative-reduce param averaging, 3 workers, 30 jobs")
    t0 = time.perf_counter()
    final = DistributedRunner().simulate(
        payloads=batches,
        performer_factory=lambda: NetworkPerformer(conf_json, epochs=2),
        aggregator=ParameterAveragingAggregator(),
        n_workers=3, timeout=300.0)
    net = MultiLayerNetwork.from_json(conf_json).init()
    net.params = jax.tree_util.tree_map(lambda a: np.asarray(a), final)
    ev = net.evaluate(X, Y)
    print(f"averaged-model accuracy after {time.perf_counter() - t0:.1f}s: "
          f"{ev.accuracy():.4f}")
    assert ev.accuracy() >= 0.9, ev.accuracy()

    print("== leg 2: same job-grab path over the HMAC-framed TCP tracker")
    server = StateTrackerServer(secret="round5").start()
    host, port = server.address
    remote = RemoteStateTracker(host, port, secret="round5")
    remote.add_worker("tcp-worker")
    remote.enqueue_job(Job(work=(X[:16].tolist(), Y[:16].tolist()),
                           job_id=1))
    job = remote.request_job("tcp-worker")
    print("job over TCP:", job.job_id, np.asarray(job.work[0]).shape)
    remote.close()
    server.stop()

    print("== leg 3: reaper recovers an orphaned job")
    tracker = StateTracker()
    tracker.add_worker("doomed")
    tracker.enqueue_job(Job(work=np.full(1, 99.0), job_id=100))
    assert tracker.request_job("doomed") is not None
    time.sleep(0.2)
    reaped = tracker.reap_stale(timeout=0.1)
    requeued = tracker.request_job("live")
    print("reaped:", reaped, "| orphaned job re-served to live worker:",
          requeued.job_id if requeued else None)
    assert requeued is not None and requeued.job_id == 100
    print("GREEN: scaleout stack end-to-end "
          "(averaging, TCP transport, reaping)")


if __name__ == "__main__":
    with capture() as buf:
        main()
    write_log("scaleout", buf.getvalue())
