"""Analysis-tier evidence: jitted KMeans on real digits, exact t-SNE
coordinates, and the run-twice determinism checker on a DP training run
(`KMeansClustering.java:31`, `Tsne.java:208`, and the race-detection
subsystem the reference never had — its Hogwild is deliberately racy)."""

from _common import capture, ensure_cpu_mesh, write_log

ensure_cpu_mesh(8)

import numpy as np  # noqa: E402


def main() -> None:
    from sklearn.datasets import load_digits

    X, y = load_digits(return_X_y=True)
    X = (X / 16.0).astype(np.float32)

    print("== leg 1: jitted KMeans (Lloyd) on sklearn digits")
    from deeplearning4j_tpu.clustering import KMeansClustering

    km = KMeansClustering.setup(10, max_iter=50, seed=0)
    assign = np.asarray(km.fit(X))
    # purity: majority true label per cluster
    purity = sum(np.bincount(y[assign == c]).max()
                 for c in range(10) if (assign == c).any()) / len(y)
    print(f"cluster purity on digits: {purity:.3f}")
    assert purity >= 0.5, purity

    print("== leg 2: exact t-SNE embeds 300 digits")
    from deeplearning4j_tpu.plot import Tsne

    sub = X[:300]
    coords = np.asarray(Tsne(n_iter=120, perplexity=20.0,
                             seed=0).fit_transform(sub))
    print("tsne coords:", coords.shape,
          "finite:", bool(np.isfinite(coords).all()))
    assert coords.shape == (300, 2) and np.isfinite(coords).all()

    print("== leg 3: run-twice determinism of a DP training run")
    from deeplearning4j_tpu.nn.conf import (
        DenseLayerConf,
        MultiLayerConfiguration,
        NeuralNetConfiguration,
        OutputLayerConf,
    )
    from deeplearning4j_tpu.runtime.determinism import (
        check_network_determinism,
    )

    conf = MultiLayerConfiguration(
        conf=NeuralNetConfiguration(learning_rate=0.05, updater="adam"),
        layers=(DenseLayerConf(n_in=64, n_out=32, activation="relu"),
                OutputLayerConf(n_in=32, n_out=10)))
    Y1h = np.eye(10, dtype=np.float32)[y[:256]]
    # raises NondeterminismError (naming the first mismatching leaf)
    # if the two fresh runs differ in any bit
    check_network_determinism(conf, X[:256], Y1h, steps=3)
    print("two independent 3-step runs bit-identical: True")
    print("GREEN: analysis tier (kmeans, t-sne, determinism)")


if __name__ == "__main__":
    with capture() as buf:
        main()
    write_log("analysis", buf.getvalue())
