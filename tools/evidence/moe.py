"""MoE evidence: Switch (top-1) and GShard-style top-2 routing with
capacity dispatch + aux load-balancing loss, trained on the dp/sp/tp/ep
mesh (expert parallelism rides the model axis) AND through the CLI
`-experts` flag — the beyond-reference tier PARITY row 68 describes."""

from _common import REPO, capture, ensure_cpu_mesh, write_log

ensure_cpu_mesh(8)

import dataclasses  # noqa: E402
import subprocess  # noqa: E402
import sys  # noqa: E402
import tempfile  # noqa: E402

import jax  # noqa: E402
import numpy as np  # noqa: E402

from deeplearning4j_tpu.parallel import make_mesh  # noqa: E402
from deeplearning4j_tpu.parallel import transformer as tfm  # noqa: E402
from deeplearning4j_tpu.parallel.hybrid import (  # noqa: E402
    HybridParallelTrainer,
)


def _data(cfg, n, seed):
    rng = np.random.default_rng(seed)
    base = np.arange(cfg.max_len) % 13 + 1
    toks = np.stack([np.roll(base, rng.integers(0, 13)) for _ in range(n)])
    return toks.astype(np.int32), np.roll(toks, -1, axis=1).astype(np.int32)


def main() -> None:
    print(f"devices: {len(jax.devices())} ({jax.default_backend()})")
    for top_k, name in ((1, "Switch top-1"), (2, "GShard top-2")):
        cfg = tfm.TransformerConfig(
            vocab_size=32, d_model=32, n_heads=4, n_layers=2, d_ff=64,
            max_len=16, n_experts=4, moe_top_k=top_k)
        mesh = make_mesh((2, 2, 2), ("data", "seq", "model"),
                         devices=jax.devices()[:8])
        tr = HybridParallelTrainer(cfg, mesh, lr=3e-3, seed=0,
                                   updater="adam")
        toks, tgts = _data(cfg, 8, seed=2)
        losses = [tr.fit_batch(toks, tgts) for _ in range(25)]
        print(f"{name} (4 experts, capacity dispatch, aux loss) on "
              f"dp/sp/tp/ep mesh: loss {losses[0]:.4f} -> {losses[-1]:.4f}")
        assert losses[-1] < losses[0] * 0.8, (name, losses)

    print("== CLI: dl4j lm -experts 2 end-to-end")
    tmp = tempfile.mkdtemp()
    corpus = f"{tmp}/c.txt"
    open(corpus, "w").write("the quick brown fox jumps the lazy dog. " * 60)
    proc = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu.cli", "lm",
         "-input", corpus, "-output", f"{tmp}/lm", "-epochs", "1",
         "-batch", "4", "-seq", "16", "-d-model", "32", "-layers", "2",
         "-heads", "4", "-experts", "2", "-generate", "the",
         "-max-new", "6", "-temperature", "0"],
        capture_output=True, text=True, cwd=REPO, timeout=900)
    for line in proc.stdout.splitlines():
        if "Platform" not in line:
            print(line)
    assert proc.returncode == 0, proc.stderr[-500:]
    print("GREEN: MoE routing trains on the ep mesh and through the CLI")


if __name__ == "__main__":
    with capture() as buf:
        main()
    write_log("moe", buf.getvalue())
