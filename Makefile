# Convenience entry points. Everything here is reproducible by hand —
# the targets just spell the one-liners out.

.PHONY: test test-serving test-precision test-fleet test-paged \
	test-procfleet dryrun bench smoke serving-smoke bench-precision \
	bench-fleet bench-paged bench-procfleet test-obs bench-obs \
	obs-smoke evidence lint test-lint test-elastic bench-elastic \
	test-spec bench-spec test-disagg bench-disagg test-pressure \
	bench-pressure test-tenancy bench-tenants test-zero bench-zero \
	test-paged-kernel bench-paged-kernel test-hibernate \
	bench-hibernate

# lint first: the four-pass static sweep is ~1s and fails fast on a
# race/host-sync/recompile-hazard/broad-except finding before the
# (much slower) runtime suite spins up.
test: lint
	python -m pytest tests/ -x -q

# Serving subsystem only (micro-batcher, bucket ladder, continuous LM).
test-serving:
	python -m pytest tests/ -q -m serving

# Serving-fleet only (failover router, health ejection/re-admission,
# rolling weight swaps, fleet chaos).
test-fleet:
	python -m pytest tests/ -q -m fleet

# Fleet bench row: concurrency-32 storm with a replica killed mid-storm
# (requests/s, p99, failed must be 0) + the shared-prefix LM leg.
bench-fleet:
	BENCH_ONLY=servingfleet python bench.py

# Process-supervision only (crash detection/classification, backoff
# restart, crash-loop quarantine, cross-host attach, launcher
# spawn/reap/log hygiene — real processes via the stdlib stub worker).
test-procfleet:
	python -m pytest tests/ -q -m procfleet

# Process-supervision bench row: 3 REAL `dl4j serve` worker processes,
# one SIGKILL'd mid-storm — failed must be 0, restart latency reported.
bench-procfleet:
	BENCH_ONLY=procfleet python bench.py

# Paged-KV tests only (block-table pool parity, radix prefix reuse +
# copy-on-write, chunked prefill, page refcount ledger under chaos,
# zero-recompile guard).
test-paged:
	python -m pytest tests/ -q -m paged

# Paged-KV bench row: shared-prefix storm, paged (half-size pool) vs
# dense — tokens/s ratio, KV bytes at equal traffic, prefix hit rate
# (docs/performance.md "The KV memory cost model").
bench-paged:
	BENCH_ONLY=paged python bench.py

# Paged-attention KERNEL plane only (fused block-table-walk flash
# attention: kernel-vs-gather-oracle parity incl. C>1 chunks, page
# straddles, null lanes, bf16/fp16 finite masks, serving byte-parity,
# zero-recompile guard — docs/performance.md "The paged-attention
# kernel cost model").
test-paged-kernel:
	python -m pytest tests/ -q -m paged_kernel

# The kernel leg rides the paged row (kernel-vs-gather decode-step wall
# time + modeled HBM bytes/step columns and the live-pages acceptance).
bench-paged-kernel: bench-paged

# Speculative-decode tests only (drafter plane: n-gram property suite +
# small-model drafter, wide verify with in-jit accept/rollback, greedy
# byte-parity vs generate() incl. adversarial drafters, rollback page
# hygiene, unsupported-combo admission, zero-recompile guard).
test-spec:
	python -m pytest tests/ -q -m spec

# Speculative-decode bench row: shared-prefix greedy storm, n-gram
# drafter vs the PR-7 paged baseline — gates tokens_per_dispatch > 1.5,
# a tokens/s win, byte-parity sentinel, balanced page ledger, zero
# off-ladder compiles (docs/performance.md "The speculative decode
# cost model").
bench-spec:
	BENCH_ONLY=speculative python bench.py

# Disaggregated-serving tests only (KV page shipping wire format +
# integrity, shipped-lane byte parity, role routing with the recompute
# failure ladder, sticky sessions, SSE streaming incl. disconnect
# hygiene).
test-disagg:
	python -m pytest tests/ -q -m disagg

# Disaggregated-serving bench row: mixed long-prompt + short-chat storm,
# 1 prefill + 2 decode workers vs 3 undifferentiated — gates decode-side
# p99 TTFT improvement and failed == 0 with a prefill worker killed
# mid-storm (docs/architecture.md "Disaggregated serving").
bench-disagg:
	BENCH_ONLY=disagg python bench.py

# Overload-survival tests only (priority admission ordering, KV lane
# preemption + host swap-out byte parity, swap eviction/corruption
# recompute fallback, brownout ladder hysteresis, pool-exhaustion
# chaos regression, role-aware autoscale signals).
test-pressure:
	python -m pytest tests/ -q -m pressure

# Tenancy-plane tests only (registry/quotas/WFQ, per-tenant 429s,
# burn-rate victim selection, fleet ledger reconciliation).
test-tenancy:
	python -m pytest tests/ -q -m tenancy

# Tiered KV state hierarchy tests only (host/disk store economy,
# quantized frames at rest, hibernate -> resume byte parity incl. a
# full process restart over the same disk dir, the disk chaos ladder;
# docs/robustness.md "The state hierarchy").
test-hibernate:
	python -m pytest tests/ -q -m hibernate

# Hibernation bench row: N idle sessions hibernated int8 to the disk
# tier under a deliberately tight host cap, then resumed COLD — gates
# at-rest bytes <= 0.3x exact, zero failed resumes, byte parity,
# balanced ledger, zero off-ladder compiles.
bench-hibernate:
	BENCH_ONLY=hibernate python bench.py

# Multi-tenant isolation bench row: tenant-B best_effort flood at 5x
# its token quota vs tenant-A's interactive wave on the same pool.
bench-tenants:
	BENCH_ONLY=tenants python bench.py

# Overload-survival bench row: a mixed-priority storm sized to >2x the
# paged pool's capacity, survival plane (priorities + preemption +
# brownout) vs the all-FIFO baseline — gates zero failed interactive
# requests, interactive p99 under the FIFO baseline, ladder
# transitions counted, pool ledger + swap byte-cap honored.
bench-pressure:
	BENCH_ONLY=pressure python bench.py

# Observability-plane tests only (metrics registry + exposition,
# request tracing across the fleet, compile watcher, training
# telemetry; docs/observability.md).
test-obs:
	python -m pytest tests/ -q -m obs

# Observability-overhead bench row: serving storm with the full
# observability plane on vs off (gate: >= 0.97x baseline requests/s).
bench-obs:
	BENCH_ONLY=obs python bench.py

# The obs CI gate: tests + the overhead row.
obs-smoke: test-obs bench-obs

# First-party static analysis (docs/static-analysis.md): lock-discipline
# race detector (LCK), jit-purity/host-sync (JIT), recompile hazards
# (RCP), broad excepts (BLE).  Fails on any finding not frozen in
# tools/dl4jlint/lint_baseline.json; < 10s budget asserted in tier-1.
lint:
	python -m tools.dl4jlint

# Lint-framework tests only (per-pass fixtures, baseline workflow, the
# zero-new-findings sweep + <10s budget gate).
test-lint:
	python -m pytest tests/ -q -m lint

# Elastic checkpoint plane only (sharded snapshots + SHA-256 integrity,
# kill-at-every-commit-boundary atomicity, N→M topology-elastic restore,
# corruption fallback, real-process kill-mid-save resume acceptance).
test-elastic:
	python -m pytest tests/ -q -m elastic

# Elastic bench row: save sharded on 4 replicas, verified restore on 2 —
# restore latency + bitwise gate + corruption-detected gate.
bench-elastic:
	BENCH_ONLY=elastic python bench.py

# Multichip dryrun (8 virtual CPU devices) + committed evidence log in
# EVIDENCE/. Safe under a wedged TPU tunnel (env decision precedes jax).
dryrun:
	python -m deeplearning4j_tpu.dryrun 8

bench:
	python bench.py

smoke:
	BENCH_ONLY=lenet,transformer python bench.py

# Serving throughput rows only (micro-batched classifier + continuous LM
# + the overload/admission-control row + the fleet mid-storm-kill row +
# the paged-KV shared-prefix row).
serving-smoke:
	BENCH_ONLY=serving,servinglm,servingoverload,servingfleet,paged,speculative,disagg,pressure,tenants python bench.py

# Precision-plane tests only (bf16-mixed parity/determinism, loss-scaler
# overflow recovery, int8 serving agreement, dtype round-trips).
test-precision:
	python -m pytest tests/ -q -m precision

# Precision-plane bench row: bf16-mixed train-state reduction, int8
# param-bytes reduction, parity guards (docs/performance.md).
bench-precision:
	BENCH_ONLY=precision python bench.py

# ZeRO-1 weight-update sharding plane only (sharded-vs-replicated fp32
# bitwise parity, loss-scale lockstep, chunked/local-SGD/clip-norm
# composition, hybrid+pipeline DP-axis moments, elastic N->M resume,
# zero-recompile guard).
test-zero:
	python -m pytest tests/ -q -m zero

# The ZeRO leg rides the precision row (composed per-replica
# train-state-bytes columns + the >=3.5x composed-reduction gate).
bench-zero: bench-precision

# Regenerate every committed EVIDENCE/ artifact (see EVIDENCE/README.md).
# Each runner re-execs itself into a scrubbed 8-virtual-CPU-device env,
# so this is safe under a wedged TPU tunnel.
evidence: dryrun
	cd tools/evidence && python longctx.py && python ui_server.py \
	  && python scaleout.py && python runtime.py && python nlp.py \
	  && python analysis.py && python profiling.py && python hybrid_training.py && python moe.py && python lm_cli.py
